"""NaiveBayes / DecisionTree / OneVsRest / ml.stat vs sklearn+scipy (SURVEY §4)."""

import numpy as np
import pytest

from orange3_spark_tpu.core.domain import ContinuousVariable, DiscreteVariable, Domain
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.datasets import make_classification
from orange3_spark_tpu.models.decision_tree import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
)
from orange3_spark_tpu.models.naive_bayes import NaiveBayes
from orange3_spark_tpu.models.one_vs_rest import OneVsRest
from orange3_spark_tpu.models.stat import (
    ChiSquareTest,
    Correlation,
    KolmogorovSmirnovTest,
    Summarizer,
)


def _counts_table(session, n=300, d=6, k=3, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, k, size=n)
    # class-dependent Poisson rates -> multinomial-like count features
    rates = rng.uniform(0.5, 5.0, size=(k, d))
    X = rng.poisson(rates[y]).astype(np.float32)
    domain = Domain(
        [ContinuousVariable(f"f{i}") for i in range(d)],
        DiscreteVariable("y", tuple(str(i) for i in range(k))),
    )
    return TpuTable.from_numpy(domain, X, y.astype(np.float32), session=session), X, y


# ------------------------------------------------------------------ NaiveBayes
def test_nb_multinomial_matches_sklearn(session):
    t, X, y = _counts_table(session)
    model = NaiveBayes(smoothing=1.0, model_type="multinomial").fit(t)

    from sklearn.naive_bayes import MultinomialNB

    sk = MultinomialNB(alpha=1.0).fit(X, y)
    np.testing.assert_allclose(
        model.predict_proba(t), sk.predict_proba(X), rtol=1e-3, atol=1e-4
    )
    assert np.mean(model.predict(t) == sk.predict(X)) == 1.0


def test_nb_bernoulli_matches_sklearn(session):
    rng = np.random.default_rng(1)
    n, d = 400, 8
    y = rng.integers(0, 2, size=n)
    p = np.where(y[:, None] == 1, 0.7, 0.3)
    X = (rng.uniform(size=(n, d)) < p).astype(np.float32)
    t = TpuTable.from_arrays(X, y.astype(np.float32), class_values=("0", "1"))
    model = NaiveBayes(smoothing=1.0, model_type="bernoulli").fit(t)

    from sklearn.naive_bayes import BernoulliNB

    sk = BernoulliNB(alpha=1.0).fit(X, y)
    np.testing.assert_allclose(
        model.predict_proba(t), sk.predict_proba(X), rtol=1e-3, atol=1e-4
    )


def test_nb_gaussian_matches_sklearn(session, iris):
    model = NaiveBayes(model_type="gaussian").fit(iris)

    from sklearn.naive_bayes import GaussianNB

    X, Y, _ = iris.to_numpy()
    sk = GaussianNB().fit(X, Y[:, 0])
    assert np.mean(model.predict(iris) == sk.predict(X)) > 0.98


def test_nb_complement_runs(session):
    t, X, y = _counts_table(session, seed=3)
    model = NaiveBayes(model_type="complement").fit(t)
    assert np.mean(model.predict(t) == y) > 0.5


def test_nb_rejects_negative_features(session):
    rng = np.random.default_rng(0)
    X = rng.standard_normal((50, 3)).astype(np.float32)
    y = rng.integers(0, 2, 50).astype(np.float32)
    t = TpuTable.from_arrays(X, y, class_values=("0", "1"))
    with pytest.raises(ValueError, match="nonnegative"):
        NaiveBayes(model_type="multinomial").fit(t)


def test_nb_checkpoint_roundtrip(session, iris):
    import pickle

    model = NaiveBayes(model_type="gaussian").fit(iris)
    clone = pickle.loads(pickle.dumps(model))
    np.testing.assert_allclose(clone.predict(iris), model.predict(iris))


# ---------------------------------------------------------------- DecisionTree
def test_dt_classifier_iris(session, iris):
    model = DecisionTreeClassifier(max_depth=4, max_bins=64).fit(iris)
    X, Y, _ = iris.to_numpy()
    assert np.mean(model.predict(iris) == Y[:, 0]) > 0.95


def test_dt_classifier_close_to_sklearn(session):
    t = make_classification(600, 6, n_classes=3, seed=9, noise=0.2, session=session)
    model = DecisionTreeClassifier(max_depth=5, max_bins=64).fit(t)

    from sklearn.tree import DecisionTreeClassifier as SkDT

    X, Y, _ = t.to_numpy()
    sk = SkDT(max_depth=5, random_state=0).fit(X, Y[:, 0])
    ours = np.mean(model.predict(t) == Y[:, 0])
    theirs = np.mean(sk.predict(X) == Y[:, 0])
    assert ours > theirs - 0.05  # binned splits vs exact splits


def test_dt_regressor(session):
    rng = np.random.default_rng(2)
    X = rng.uniform(-2, 2, size=(500, 3)).astype(np.float32)
    y = (np.sign(X[:, 0]) + 0.5 * np.sign(X[:, 1])).astype(np.float32)
    t = TpuTable.from_arrays(X, y)
    model = DecisionTreeRegressor(max_depth=4, max_bins=32).fit(t)
    pred = model.predict(t)
    assert np.mean((pred - y) ** 2) < 0.05


def test_dt_transform_appends_prediction(session, iris):
    out = DecisionTreeClassifier(max_depth=3).fit(iris).transform(iris)
    assert "prediction" in [v.name for v in out.domain.attributes]


def test_dt_bad_impurity_raises(session, iris):
    with pytest.raises(ValueError, match="gini"):
        DecisionTreeClassifier(impurity="variance").fit(iris)


# ------------------------------------------------------------------ OneVsRest
def test_ovr_with_linear_svc(session, iris):
    from orange3_spark_tpu.models.linear_svc import LinearSVC

    model = OneVsRest(LinearSVC(max_iter=100, reg_param=0.01)).fit(iris)
    X, Y, _ = iris.to_numpy()
    assert np.mean(model.predict(iris) == Y[:, 0]) > 0.9


def test_ovr_with_logreg_matches_direct_quality(session, iris):
    from orange3_spark_tpu.models.logistic_regression import LogisticRegression

    model = OneVsRest(LogisticRegression(max_iter=100)).fit(iris)
    X, Y, _ = iris.to_numpy()
    assert np.mean(model.predict(iris) == Y[:, 0]) > 0.93
    assert len(model.models) == 3


def test_ovr_transform_on_padded_table(session, iris):
    from orange3_spark_tpu.models.logistic_regression import LogisticRegression

    # iris has 150 rows -> padded to 152 on the 8-device mesh; transform must
    # emit a full padded column, not crash on the length mismatch
    model = OneVsRest(LogisticRegression(max_iter=50)).fit(iris)
    out = model.transform(iris)
    assert out.n_pad == iris.n_pad
    assert "prediction" in [v.name for v in out.domain.attributes]


def test_nb_bernoulli_rejects_non_binary(session):
    rng = np.random.default_rng(11)
    X = rng.integers(0, 3, size=(60, 4)).astype(np.float32)  # has 2s
    y = rng.integers(0, 2, 60).astype(np.float32)
    t = TpuTable.from_arrays(X, y, class_values=("0", "1"))
    with pytest.raises(ValueError, match="0/1"):
        NaiveBayes(model_type="bernoulli").fit(t)


# -------------------------------------------------------------------- ml.stat
def test_pearson_matches_numpy(session):
    rng = np.random.default_rng(4)
    X = rng.standard_normal((300, 5)).astype(np.float32)
    X[:, 1] = 0.8 * X[:, 0] + 0.2 * X[:, 1]
    t = TpuTable.from_arrays(X)
    corr = Correlation.corr(t, "pearson")
    np.testing.assert_allclose(corr, np.corrcoef(X.T), rtol=1e-3, atol=1e-4)


def test_spearman_matches_scipy(session):
    rng = np.random.default_rng(5)
    X = rng.integers(0, 10, size=(200, 4)).astype(np.float32)  # heavy ties
    t = TpuTable.from_arrays(X)
    corr = Correlation.corr(t, "spearman")

    from scipy.stats import spearmanr

    ref = spearmanr(X).statistic
    np.testing.assert_allclose(corr, ref, rtol=1e-3, atol=1e-4)


def test_spearman_ignores_padding_and_filtered(session):
    # same live data, once bare (37 rows -> 3 padding slots) and once diluted
    # with explicit zero-weight garbage rows -> identical ranks/correlation
    rng = np.random.default_rng(6)
    X = rng.standard_normal((37, 3)).astype(np.float32)
    c1 = Correlation.corr(TpuTable.from_arrays(X), "spearman")
    garbage = 100.0 * rng.standard_normal((11, 3)).astype(np.float32)
    X2 = np.concatenate([X, garbage], axis=0)
    W2 = np.concatenate([np.ones(37), np.zeros(11)]).astype(np.float32)
    from orange3_spark_tpu.core.domain import ContinuousVariable, Domain

    t2 = TpuTable.from_numpy(
        Domain([ContinuousVariable(f"x{i}") for i in range(3)], None), X2, W=W2
    )
    c2 = Correlation.corr(t2, "spearman")
    np.testing.assert_allclose(c1, c2, atol=1e-5)

    from scipy.stats import spearmanr

    np.testing.assert_allclose(c1, spearmanr(X).statistic, rtol=1e-3, atol=1e-4)


def test_chi_square_matches_scipy(session):
    rng = np.random.default_rng(7)
    n = 500
    y = rng.integers(0, 3, size=n)
    f0 = (y + rng.integers(0, 2, size=n)) % 4       # dependent feature
    f1 = rng.integers(0, 5, size=n)                 # independent feature
    X = np.stack([f0, f1], axis=1).astype(np.float32)
    domain = Domain(
        [ContinuousVariable("f0"), ContinuousVariable("f1")],
        DiscreteVariable("y", ("0", "1", "2")),
    )
    t = TpuTable.from_numpy(domain, X, y.astype(np.float32), session=session)
    res = ChiSquareTest.test(t)

    from scipy.stats import chi2_contingency

    for j in range(2):
        obs = np.zeros((int(X[:, j].max()) + 1, 3))
        np.add.at(obs, (X[:, j].astype(int), y), 1.0)
        obs = obs[obs.sum(1) > 0][:, obs.sum(0) > 0]
        ref = chi2_contingency(obs, correction=False)
        np.testing.assert_allclose(res.statistics[j], ref.statistic, rtol=1e-4)
        np.testing.assert_allclose(res.p_values[j], ref.pvalue, rtol=1e-3, atol=1e-6)
    assert res.p_values[0] < 0.01 < res.p_values[1]


def test_summarizer(session):
    rng = np.random.default_rng(8)
    X = rng.standard_normal((123, 4)).astype(np.float32)
    X[X < -1.5] = 0.0
    t = TpuTable.from_arrays(X)
    s = Summarizer.metrics(t)
    np.testing.assert_allclose(s.mean, X.mean(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(s.variance, X.var(0, ddof=1), rtol=1e-3, atol=1e-5)
    assert s.count == 123
    np.testing.assert_allclose(s.num_non_zeros, (X != 0).sum(0))
    np.testing.assert_allclose(s.max, X.max(0), rtol=1e-5)
    np.testing.assert_allclose(s.min, X.min(0), rtol=1e-5)
    np.testing.assert_allclose(s.norm_l1, np.abs(X).sum(0), rtol=1e-4)
    np.testing.assert_allclose(s.norm_l2, np.sqrt((X**2).sum(0)), rtol=1e-4)


def test_ks_test_matches_scipy(session):
    rng = np.random.default_rng(9)
    x = rng.standard_normal(400).astype(np.float32)[:, None]
    t = TpuTable.from_arrays(x)
    res = KolmogorovSmirnovTest.test(t, "x0", "norm", loc=0.0, scale=1.0)

    from scipy.stats import kstest

    ref = kstest(x[:, 0], "norm")
    np.testing.assert_allclose(res.statistic, ref.statistic, rtol=1e-3, atol=1e-5)
    assert abs(res.p_value - ref.pvalue) < 0.02  # asymptotic vs exact tail
    # a shifted normal must be strongly rejected
    res2 = KolmogorovSmirnovTest.test(t, "x0", "norm", loc=2.0, scale=1.0)
    assert res2.p_value < 1e-6


def test_anova_test_matches_sklearn(session):
    """ANOVATest (pyspark.ml.stat 3.1) == sklearn f_classif on uniform
    weights; weighted rows == row duplication."""
    from orange3_spark_tpu.models.stat import ANOVATest

    rng = np.random.default_rng(9)
    n, d, k = 400, 5, 3
    y = rng.integers(0, k, size=n)
    X = rng.standard_normal((n, d)).astype(np.float32)
    X[:, 0] += y * 0.8                               # strongly dependent
    domain = Domain(
        [ContinuousVariable(f"f{i}") for i in range(d)],
        DiscreteVariable("y", tuple(str(i) for i in range(k))),
    )
    t = TpuTable.from_numpy(domain, X, y.astype(np.float32), session=session)
    res = ANOVATest.test(t)

    from sklearn.feature_selection import f_classif

    F, p = f_classif(X, y)
    np.testing.assert_allclose(res.f_values, F, rtol=2e-3)
    np.testing.assert_allclose(res.p_values, p, rtol=5e-3, atol=1e-6)
    assert res.p_values[0] < 1e-6
    np.testing.assert_array_equal(res.degrees_of_freedom[0], [k - 1, n - k])

    # integer weights behave like row duplication
    wdup = rng.integers(1, 4, size=n)
    t_w = TpuTable.from_numpy(domain, X, y.astype(np.float32),
                              W=wdup.astype(np.float32), session=session)
    Xdup = np.repeat(X, wdup, axis=0)
    ydup = np.repeat(y, wdup)
    Fd, _ = f_classif(Xdup, ydup)
    np.testing.assert_allclose(ANOVATest.test(t_w).f_values, Fd, rtol=2e-3)


def test_fvalue_test_matches_sklearn(session):
    """FValueTest (pyspark.ml.stat 3.1) == sklearn f_regression."""
    from orange3_spark_tpu.models.stat import FValueTest

    rng = np.random.default_rng(10)
    n, d = 350, 4
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (0.9 * X[:, 1] + 0.1 * rng.standard_normal(n)).astype(np.float32)
    domain = Domain(
        [ContinuousVariable(f"f{i}") for i in range(d)],
        ContinuousVariable("y"),
    )
    t = TpuTable.from_numpy(domain, X, y, session=session)
    res = FValueTest.test(t)

    from sklearn.feature_selection import f_regression

    F, p = f_regression(X, y)
    np.testing.assert_allclose(res.f_values, F, rtol=2e-3)
    np.testing.assert_allclose(res.p_values, p, rtol=5e-3, atol=1e-6)
    assert res.p_values[1] < 1e-10 and res.p_values[0] > 1e-4
    np.testing.assert_array_equal(res.degrees_of_freedom[0], [1, n - 2])


def test_anova_unobserved_class_df(session):
    """A class index never observed among live rows must not inflate
    df_between (sklearn/Spark count distinct PRESENT classes)."""
    from orange3_spark_tpu.models.stat import ANOVATest

    rng = np.random.default_rng(11)
    n = 200
    y = rng.choice([0, 2], size=n)            # class 1 never occurs
    X = (rng.standard_normal((n, 3)) + y[:, None] * 0.5).astype(np.float32)
    domain = Domain(
        [ContinuousVariable(f"f{i}") for i in range(3)],
        DiscreteVariable("y", ("0", "1", "2")),
    )
    t = TpuTable.from_numpy(domain, X, y.astype(np.float32), session=session)
    res = ANOVATest.test(t)

    from sklearn.feature_selection import f_classif

    F, p = f_classif(X, y)
    np.testing.assert_allclose(res.f_values, F, rtol=2e-3)
    np.testing.assert_allclose(res.p_values, p, rtol=5e-3, atol=1e-6)
    np.testing.assert_array_equal(res.degrees_of_freedom[0], [1, n - 2])


def test_multivariate_gaussian_matches_scipy(session):
    """MultivariateGaussian (pyspark.ml.stat.distribution) pdf/logpdf ==
    scipy, including a singular covariance (pseudo-det/pseudo-inverse)."""
    from orange3_spark_tpu.models.stat import MultivariateGaussian

    rng = np.random.default_rng(12)
    d = 4
    A = rng.standard_normal((d, d))
    cov = (A @ A.T + 0.5 * np.eye(d)).astype(np.float32)
    mean = rng.standard_normal(d).astype(np.float32)
    pts = rng.standard_normal((32, d)).astype(np.float32)

    from scipy.stats import multivariate_normal

    g = MultivariateGaussian(mean, cov)
    ref = multivariate_normal(mean, cov)
    np.testing.assert_allclose(np.asarray(g.logpdf(pts)),
                               ref.logpdf(pts), rtol=2e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(g.pdf(pts[0])),
                               ref.pdf(pts[0]), rtol=2e-3)

    # rank-deficient covariance: project onto a 2-D subspace. Build the
    # singular matrix in FLOAT64 (an f32-rounded one carries ~1e-9 noise
    # eigenvalues that read as extra rank). MLlib normalizes by the FULL
    # dimension (d*log(2pi) + log pseudo-det); scipy's allow_singular
    # uses the rank — shift scipy by 0.5*(d-r)*log(2pi).
    B = rng.standard_normal((d, 2))
    cov_sing = B @ B.T
    g_s = MultivariateGaussian(np.zeros(d), cov_sing)
    ref_s = multivariate_normal(np.zeros(d), cov_sing, allow_singular=True)
    pts_in = (rng.standard_normal((8, 2)) @ B.T).astype(np.float32)
    shift = 0.5 * (d - 2) * np.log(2.0 * np.pi)
    np.testing.assert_allclose(np.asarray(g_s.logpdf(pts_in)),
                               ref_s.logpdf(pts_in) - shift,
                               rtol=2e-3, atol=2e-3)

    # MLlib convention: an all-zero covariance is an error, not rank 0
    import pytest
    with pytest.raises(ValueError, match="no non-zero eigenvalue"):
        MultivariateGaussian(np.zeros(d, np.float32),
                             np.zeros((d, d), np.float32))
