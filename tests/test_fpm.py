"""FPGrowth / PrefixSpan vs known ground truth (SURVEY §4)."""

import numpy as np
import pytest

from orange3_spark_tpu.core.domain import ContinuousVariable, Domain, StringVariable
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.fpm import FPGrowth, PrefixSpan


def _basket_table(session, baskets):
    dom = Domain([ContinuousVariable("x")], None, [StringVariable("items")])
    X = np.zeros((len(baskets), 1), dtype=np.float32)
    metas = np.empty((len(baskets), 1), dtype=object)
    for i, b in enumerate(baskets):
        metas[i, 0] = b
    return TpuTable.from_numpy(dom, X, metas=metas, session=session)


BASKETS = [
    ["bread", "milk"],
    ["bread", "diapers", "beer", "eggs"],
    ["milk", "diapers", "beer", "cola"],
    ["bread", "milk", "diapers", "beer"],
    ["bread", "milk", "diapers", "cola"],
]


def test_fpgrowth_frequent_itemsets(session):
    t = _basket_table(session, BASKETS)
    model = FPGrowth(min_support=0.6, items_col="items").fit(t)
    sets = {tuple(f["items"]): f["freq"] for f in model.freq_itemsets()}
    # classic textbook result: {beer,diapers} support 3/5
    assert sets[("bread",)] == 4.0
    assert sets[("milk",)] == 4.0
    assert sets[("diapers",)] == 4.0
    assert sets[("beer", "diapers")] == 3.0
    assert ("beer",) in sets and sets[("beer",)] == 3.0
    # {beer, cola} has support 1/5 -> absent
    assert ("beer", "cola") not in sets


def test_fpgrowth_matches_mlxtend_style_bruteforce(session):
    rng = np.random.default_rng(0)
    items = list("abcdef")
    baskets = [
        [it for it in items if rng.random() < 0.4] or ["a"] for _ in range(120)
    ]
    t = _basket_table(session, baskets)
    model = FPGrowth(min_support=0.25, items_col="items").fit(t)
    got = {frozenset(f["items"]): f["freq"] for f in model.freq_itemsets()}
    # brute force
    import itertools as itl

    min_count = 0.25 * len(baskets)
    expect = {}
    for r in range(1, 4):
        for combo in itl.combinations(items, r):
            c = sum(1 for b in baskets if set(combo) <= set(b))
            if c >= min_count:
                expect[frozenset(combo)] = float(c)
    for s, c in expect.items():
        assert got.get(s) == c, (sorted(s), c, got.get(s))
    # no false positives at sizes 1..3
    assert all(len(s) > 3 or s in expect for s in got)


def test_fpgrowth_association_rules_and_transform(session):
    t = _basket_table(session, BASKETS)
    model = FPGrowth(min_support=0.5, min_confidence=0.7, items_col="items").fit(t)
    rules = model.association_rules_
    assert any(r["antecedent"] == ["beer"] and r["consequent"] == ["diapers"]
               for r in rules)
    r = next(r for r in rules if r["antecedent"] == ["beer"])
    assert abs(r["confidence"] - 1.0) < 1e-9  # beer always with diapers
    assert r["lift"] == pytest.approx(1.0 / (4 / 5))
    out = model.transform(t)
    names = [v.name for v in out.domain.attributes]
    assert any(n.startswith("pred_") for n in names)
    X = out.to_numpy()[0]
    j = names.index("pred_diapers")
    assert X[0, j] == 1.0  # basket 0 {bread, milk} -> rules imply diapers


def test_fpgrowth_on_binary_columns(session):
    # items_col="" mode: attributes ARE the items
    X = np.array([[1, 1, 0], [1, 0, 0], [1, 1, 1], [0, 1, 0]], np.float32)
    t = TpuTable.from_arrays(X, attr_names=["a", "b", "c"], session=session)
    model = FPGrowth(min_support=0.5).fit(t)
    sets = {tuple(f["items"]): f["freq"] for f in model.freq_itemsets()}
    assert sets[("a",)] == 3.0 and sets[("b",)] == 3.0
    assert sets[("a", "b")] == 2.0


def _seq_table(session, seqs):
    dom = Domain([ContinuousVariable("x")], None, [StringVariable("sequence")])
    X = np.zeros((len(seqs), 1), dtype=np.float32)
    metas = np.empty((len(seqs), 1), dtype=object)
    for i, s in enumerate(seqs):
        metas[i, 0] = s
    return TpuTable.from_numpy(dom, X, metas=metas, session=session)


def test_prefixspan_basic(session):
    seqs = [
        [["a"], ["b"], ["c"]],
        [["a"], ["c"]],
        [["a"], ["b"]],
        [["b"], ["c"]],
    ]
    t = _seq_table(session, seqs)
    ps = PrefixSpan(min_support=0.5, sequence_col="sequence")
    pats = {tuple(tuple(e) for e in r["sequence"]): r["freq"]
            for r in ps.find_frequent_sequential_patterns(t)}
    assert pats[(("a",),)] == 3
    assert pats[(("b",),)] == 3
    assert pats[(("c",),)] == 3
    assert pats[(("a",), ("b",))] == 2
    assert pats[(("a",), ("c",))] == 2
    assert pats[(("b",), ("c",))] == 2
    # order matters: c then a never happens
    assert (("c",), ("a",)) not in pats


def test_prefixspan_itemset_elements(session):
    # multi-item elements: <(a b)> must be found as one element, and
    # <(a b) c> as the two-element sequential pattern
    seqs = [
        [["a", "b"], ["c"]],
        [["b", "a"], ["c"]],
        [["a", "b"], ["d"]],
    ]
    t = _seq_table(session, seqs)
    ps = PrefixSpan(min_support=0.9, sequence_col="sequence")
    pats = {tuple(tuple(sorted(e)) for e in r["sequence"]): r["freq"]
            for r in ps.find_frequent_sequential_patterns(t)}
    assert pats[(("a", "b"),)] == 3
    assert pats[(("a",),)] == 3 and pats[(("b",),)] == 3
    ps2 = PrefixSpan(min_support=0.6, sequence_col="sequence")
    pats2 = {tuple(tuple(sorted(e)) for e in r["sequence"]): r["freq"]
             for r in ps2.find_frequent_sequential_patterns(t)}
    assert pats2[(("a", "b"), ("c",))] == 2


def test_prefixspan_max_pattern_length(session):
    seqs = [[["a"], ["b"], ["c"], ["d"]]] * 4
    t = _seq_table(session, seqs)
    ps = PrefixSpan(min_support=0.9, max_pattern_length=2, sequence_col="sequence")
    pats = ps.find_frequent_sequential_patterns(t)
    assert max(len(r["sequence"]) for r in pats) == 2
