"""Multi-host ingest (io/multihost.py): the make_array_from_process_local_data
assembly path, process row-slicing, and file-shard assignment — exercised
single-process (the multi-process branch runs with force_global=True, where
one process's local block IS the global array)."""

import jax
import numpy as np
import pytest

from orange3_spark_tpu.io.multihost import (
    process_row_slice,
    put_sharded,
    shard_paths,
)


def test_put_sharded_global_assembly_matches_device_put(session):
    x = np.arange(64 * 3, dtype=np.float32).reshape(64, 3)
    sh = session.row_sharding
    a = put_sharded(x, sh)
    b = put_sharded(x, sh, force_global=True)  # multi-process code path
    assert b.shape == (64, 3)
    assert b.sharding == sh
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_put_sharded_feeds_table_and_fit(session):
    """A table built through the global-assembly path must behave like the
    plain one end to end (fit + predict)."""
    from orange3_spark_tpu.core.domain import (
        ContinuousVariable, DiscreteVariable, Domain,
    )
    from orange3_spark_tpu.core.table import TpuTable
    from orange3_spark_tpu.models.logistic_regression import LogisticRegression

    rng = np.random.default_rng(0)
    X = rng.standard_normal((400, 4)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float32)
    dom = Domain([ContinuousVariable(f"f{i}") for i in range(4)],
                 DiscreteVariable("y", ("0", "1")))
    t = TpuTable.from_numpy(dom, X, y, session=session)
    m = LogisticRegression(max_iter=100).fit(t)
    assert np.mean(m.predict(t) == y) > 0.95


def test_process_row_slice_partitions_exactly(monkeypatch):
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    slices = []
    for pi in range(4):
        monkeypatch.setattr(jax, "process_index", lambda pi=pi: pi)
        slices.append(process_row_slice(10))
    covered = [i for s in slices for i in range(s.start, s.stop)]
    assert covered == list(range(10))          # disjoint, complete, ordered
    sizes = [s.stop - s.start for s in slices]
    assert max(sizes) - min(sizes) <= 1        # near-equal


def test_shard_paths_round_robin(monkeypatch):
    paths = [f"part-{i:03d}.csv" for i in range(7)]
    monkeypatch.setattr(jax, "process_count", lambda: 3)
    seen = []
    for pi in range(3):
        monkeypatch.setattr(jax, "process_index", lambda pi=pi: pi)
        seen.append(shard_paths(paths))
    flat = sorted(p for sub in seen for p in sub)
    assert flat == sorted(paths)               # every file exactly once
    assert all(len(s) in (2, 3) for s in seen)


def test_single_process_defaults():
    assert process_row_slice(100) == slice(0, 100)
    assert shard_paths(["b", "a"]) == ["a", "b"]


def test_shard_row_groups_partitions_single_parquet(tmp_path, monkeypatch):
    """Single-file parquet multihost splitting: the per-process row-group
    slices are contiguous, disjoint, exhaustive — and streaming each
    process's slice reassembles exactly the whole file (Spark's parquet
    input splits)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    import jax

    from orange3_spark_tpu.io.multihost import shard_row_groups
    from orange3_spark_tpu.io.streaming import parquet_raw_chunk_source

    p = str(tmp_path / "d.parquet")
    data = np.arange(70, dtype=np.float32)
    pq.write_table(pa.table({"v": data}), p, row_group_size=10)  # 7 groups

    monkeypatch.setattr(jax, "process_count", lambda: 3)
    slices = []
    for pi in range(3):
        monkeypatch.setattr(jax, "process_index", lambda pi=pi: pi)
        slices.append(shard_row_groups(p))
    assert [len(s) for s in slices] == [3, 2, 2]     # 7 groups over 3 procs
    assert sorted(sum(slices, [])) == list(range(7))
    for s in slices:                                  # contiguous ranges
        assert s == list(range(s[0], s[0] + len(s)))

    got = np.concatenate([
        np.concatenate(list(parquet_raw_chunk_source(
            p, chunk_rows=8, row_groups=tuple(s))()))
        for s in slices
    ])
    np.testing.assert_array_equal(got[:, 0], data)


# ======================================================================
# ISSUE 18: lockstep sharded ingestion, partitioners, gang launcher
# ======================================================================

import os  # noqa: E402
import sys  # noqa: E402
import time  # noqa: E402

from orange3_spark_tpu.io.multihost import (  # noqa: E402
    RaggedHostBlockError,
    lockstep_rows,
)


def _shared_csv(tmp_path, n, d=4, seed=0, name="shared.csv"):
    """%.9g round-trips float32 exactly — bitwise comparisons below are
    against the same bits every reader decodes."""
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float32)
    p = str(tmp_path / name)
    header = ",".join([f"f{i}" for i in range(d)] + ["y"])
    np.savetxt(p, np.column_stack([X, y]), delimiter=",", fmt="%.9g",
               header=header, comments="")
    return p, X, y


def test_put_sharded_ragged_block_raises_typed(session):
    """A block that can't tile the local row shards must fail TYPED and
    name the fix (the weight-mask pad convention), not as an opaque jax
    assembly error; a tiling block passes through the same branch."""
    bad = np.zeros((10, 3), np.float32)          # 10 % 8 local shards != 0
    with pytest.raises(RaggedHostBlockError) as ei:
        put_sharded(bad, session.row_sharding, force_global=True)
    msg = str(ei.value)
    assert "w=0" in msg and "lockstep_rows" in msg
    ok = put_sharded(np.ones((16, 3), np.float32), session.row_sharding,
                     force_global=True)
    assert ok.shape == (16, 3)


def test_lockstep_rows_is_largest_slice(monkeypatch):
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    widths = []
    for pi in range(4):
        monkeypatch.setattr(jax, "process_index", lambda pi=pi: pi)
        s = process_row_slice(10)
        widths.append(s.stop - s.start)
    assert lockstep_rows(10) == max(widths) == 3
    assert lockstep_rows(12) == 3                # even split: no padding


def test_sharded_csv_kill_switch_is_plain_source(tmp_path, monkeypatch):
    """OTPU_MULTIHOST=0: the single-path form IS csv_chunk_source —
    byte-identical chunks, same tuple shapes."""
    from orange3_spark_tpu.io.streaming import (
        csv_chunk_source, sharded_csv_chunk_source,
    )
    p, X, y = _shared_csv(tmp_path, 1000)
    monkeypatch.setenv("OTPU_MULTIHOST", "0")
    got = list(sharded_csv_chunk_source(p, "y", shard_total_rows=1000,
                                        chunk_rows=256)())
    ref = list(csv_chunk_source(p, "y", chunk_rows=256)())
    assert len(got) == len(ref)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(g[0], r[0])
        np.testing.assert_array_equal(g[1], r[1])


def test_sharded_csv_single_process_matches_plain(tmp_path):
    """Switch ON, one process: same values as the plain stream (the
    pass-through re-chunk), w None on pure chunks."""
    from orange3_spark_tpu.io.streaming import (
        csv_chunk_source, sharded_csv_chunk_source,
    )
    p, X, y = _shared_csv(tmp_path, 1000)
    got = list(sharded_csv_chunk_source(p, "y", shard_total_rows=1000,
                                        chunk_rows=256)())
    ref = list(csv_chunk_source(p, "y", chunk_rows=256)())
    assert [len(c[0]) for c in got] == [len(c[0]) for c in ref]
    np.testing.assert_array_equal(np.concatenate([c[0] for c in got]), X)
    np.testing.assert_array_equal(np.concatenate([c[1] for c in got]), y)
    assert all(c[2] is None for c in got)


def test_sharded_csv_two_process_lockstep_schedule(tmp_path, monkeypatch):
    """The lockstep contract: 1001 rows over 2 processes — rows split
    501/500, yet BOTH processes must emit the identical chunk schedule
    ([256, 245]); the short process tops up with one dead w=0 row. Naive
    slice-at-parser-chunk-granularity would emit different chunk counts
    per process and deadlock the global collectives."""
    from orange3_spark_tpu.io.streaming import sharded_csv_chunk_source
    p, X, y = _shared_csv(tmp_path, 1001)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    per_proc = []
    for pi in range(2):
        monkeypatch.setattr(jax, "process_index", lambda pi=pi: pi)
        per_proc.append(list(sharded_csv_chunk_source(
            p, "y", shard_total_rows=1001, chunk_rows=256)()))
    sched0 = [len(c[0]) for c in per_proc[0]]
    sched1 = [len(c[0]) for c in per_proc[1]]
    assert sched0 == sched1 == [256, 245]        # identical on every rank
    X0 = np.concatenate([c[0] for c in per_proc[0]])
    np.testing.assert_array_equal(X0, X[:501])
    X1 = np.concatenate([c[0] for c in per_proc[1]])
    np.testing.assert_array_equal(X1[:500], X[501:])
    np.testing.assert_array_equal(X1[500], np.zeros(4, np.float32))
    w_last = per_proc[1][-1][2]
    assert w_last is not None
    assert w_last[-1] == 0.0                     # the dead row is masked
    assert w_last[:-1].min() == 1.0              # real rows keep weight


def test_sharded_csv_overstated_rows_raises(tmp_path):
    from orange3_spark_tpu.io.streaming import sharded_csv_chunk_source
    p, _, _ = _shared_csv(tmp_path, 100)
    src = sharded_csv_chunk_source(p, "y", shard_total_rows=500,
                                   chunk_rows=64)
    with pytest.raises(ValueError, match="overstates"):
        list(src())


def test_parquet_shard_flag_splits_and_kill_switch_doesnt(tmp_path,
                                                          monkeypatch):
    """``shard=True`` makes the parquet source pick this process's
    contiguous row-group range itself; under OTPU_MULTIHOST=0 the flag is
    inert (full file)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from orange3_spark_tpu.io.streaming import parquet_raw_chunk_source

    p = str(tmp_path / "d.parquet")
    data = np.arange(70, dtype=np.float32)
    pq.write_table(pa.table({"v": data}), p, row_group_size=10)

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    parts = []
    for pi in range(2):
        monkeypatch.setattr(jax, "process_index", lambda pi=pi: pi)
        parts.append(np.concatenate(
            list(parquet_raw_chunk_source(p, chunk_rows=16, shard=True)())))
    np.testing.assert_array_equal(np.concatenate(parts)[:, 0], data)
    assert len(parts[0]) == 40 and len(parts[1]) == 30   # 4+3 groups

    monkeypatch.setenv("OTPU_MULTIHOST", "0")
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    full = np.concatenate(
        list(parquet_raw_chunk_source(p, chunk_rows=16, shard=True)()))
    np.testing.assert_array_equal(full[:, 0], data)


def test_data_parallel_partitioner_fit_and_kill_switch_parity(tmp_path,
                                                              monkeypatch):
    """The partitioner plugs into fit_stream as a session factory + source
    facade, and OTPU_MULTIHOST=0 reproduces the stock path BITWISE."""
    from orange3_spark_tpu.io.streaming import StreamingLinearEstimator
    from orange3_spark_tpu.parallel import DataParallelPartitioner

    p, X, y = _shared_csv(tmp_path, 2048)

    def fit():
        part = DataParallelPartitioner()
        src = part.shard_csv(p, "y", n_total=2048, chunk_rows=256)
        est = StreamingLinearEstimator(loss="logistic", epochs=3,
                                       step_size=0.1, chunk_rows=256)
        m = est.fit_stream(src, n_features=4, session=part.session)
        return part, np.asarray(m.coef), np.asarray(m.intercept)

    monkeypatch.setenv("OTPU_MULTIHOST", "1")
    part_on, coef_on, icpt_on = fit()
    assert part_on.enabled and part_on.mesh.shape["data"] == 8

    monkeypatch.setenv("OTPU_MULTIHOST", "0")
    part_off, coef_off, icpt_off = fit()
    assert not part_off.enabled
    np.testing.assert_array_equal(coef_on, coef_off)     # bitwise pin
    np.testing.assert_array_equal(icpt_on, icpt_off)

    # the fit means something: it separates the planted boundary
    scores = X @ coef_on + icpt_on
    pred = scores.argmax(axis=1) if scores.ndim == 2 else (scores > 0)
    assert np.mean(pred == y) > 0.9


def test_spmd_partitioner_mesh_and_state_sharding(monkeypatch):
    from orange3_spark_tpu.parallel import SPMDPartitioner

    monkeypatch.setenv("OTPU_MULTIHOST", "1")
    part = SPMDPartitioner(model_parallel=2)
    assert part.mesh.shape["data"] == 4 and part.mesh.shape["model"] == 2
    # the hashed table shards over the model axis, everything else
    # (and every vector) replicates
    emb_sh = part.state_sharding("emb", np.zeros((32, 4), np.float32))
    assert emb_sh.spec[0] == part.model_axis
    assert part.state_sharding("bias", np.zeros((4,), np.float32)
                               ) == part.session.replicated
    assert part.state_sharding("emb", np.zeros((4,), np.float32)
                               ) == part.session.replicated
    st = part.shard_state({"emb": np.ones((32, 4), np.float32),
                           "opt": {"m": np.zeros((4,), np.float32)}})
    assert st["emb"].sharding.spec[0] == part.model_axis
    with pytest.raises(ValueError, match="does not divide"):
        SPMDPartitioner(model_parallel=3)


def test_partitioner_partition_runs_donated_step(monkeypatch):
    from orange3_spark_tpu.parallel import DataParallelPartitioner

    monkeypatch.setenv("OTPU_MULTIHOST", "1")
    part = DataParallelPartitioner()
    step = part.partition(lambda st, x: {"w": st["w"] + x.sum()})
    st = part.shard_state({"w": np.float32(1.0)})
    Xb, yb, wb = part.shard_batch(np.ones((16, 2), np.float32))
    assert Xb.sharding.spec[0] == part.data_axis and yb is None and wb is None
    out = step(st, Xb)
    assert float(out["w"]) == 33.0


def test_launcher_lost_host_is_typed(tmp_path):
    """A dead rank with no restart budget surfaces as HostLostError
    carrying rank + exit code — never a hang."""
    from orange3_spark_tpu.parallel.launcher import (
        HostLostError, MultihostLauncher,
    )

    def argv(rank, n, coord):
        code = "import sys; sys.exit(3)" if rank == 1 else "pass"
        return [sys.executable, "-c", code]

    lau = MultihostLauncher(argv, 2, env=dict(os.environ),
                            log_dir=str(tmp_path / "logs"),
                            max_gang_restarts=0, wall_s=60.0)
    with pytest.raises(HostLostError) as ei:
        lau.run()
    assert ei.value.rank == 1
    assert ei.value.returncode == 3
    assert ei.value.restarts == 0


def test_launcher_wall_budget_wedge_is_typed(tmp_path):
    from orange3_spark_tpu.parallel.launcher import (
        HostLostError, MultihostLauncher,
    )
    argv = lambda r, n, c: [sys.executable, "-c", "import time; time.sleep(60)"]
    lau = MultihostLauncher(argv, 2, env=dict(os.environ),
                            log_dir=str(tmp_path / "logs"),
                            max_gang_restarts=0, wall_s=0.5)
    with pytest.raises(HostLostError, match="wedged"):
        lau.run()


def test_launcher_gang_restart_recovers(tmp_path, monkeypatch):
    """First gang loses rank 1 (exactly once, marker-armed); the launcher
    restarts the whole gang with backoff and the second attempt succeeds."""
    from orange3_spark_tpu.parallel.launcher import MultihostLauncher

    marker = str(tmp_path / "rank1.died")

    def argv(rank, n, coord):
        if rank == 1:
            code = (f"import os, sys\n"
                    f"m = {marker!r}\n"
                    "if not os.path.exists(m):\n"
                    "    open(m, 'w').close()\n"
                    "    sys.exit(9)\n")
        else:
            code = "pass"
        return [sys.executable, "-c", code]

    monkeypatch.setenv("OTPU_RETRY_BASE_S", "0.01")
    lau = MultihostLauncher(argv, 2, env=dict(os.environ),
                            log_dir=str(tmp_path / "logs"),
                            max_gang_restarts=2, wall_s=60.0)
    res = lau.run()
    assert res.n_processes == 2
    assert res.hosts_lost == 1
    assert res.gang_restarts == 1
    assert res.gang_starts == 2


def test_align_checkpoints_common_step_and_donor_copy(tmp_path):
    """A kill between two ranks' epoch saves: the gang must re-enter at
    ONE step. The min saved step wins; the ahead rank gets a donor copy
    (replicated state — any rank's snapshot at S is every rank's)."""
    import pickle
    from orange3_spark_tpu.parallel.launcher import MultihostLauncher

    def put(rank, step):
        with open(tmp_path / f"rank{rank}.ckpt", "wb") as f:
            pickle.dump({"step": step, "state": {"w": float(step)},
                         "meta": None}, f)

    put(0, 16)
    put(1, 8)
    assert MultihostLauncher.align_checkpoints(str(tmp_path), 2) == 8
    for rank in range(2):
        with open(tmp_path / f"rank{rank}.ckpt", "rb") as f:
            blob = pickle.load(f)
        assert blob["step"] == 8                 # both resume at 8
        assert blob["state"] == {"w": 8.0}

    # a rank with NO snapshot forces a clean from-scratch restart
    put(0, 16)
    os.unlink(tmp_path / "rank1.ckpt")
    assert MultihostLauncher.align_checkpoints(str(tmp_path), 2) == 0
    assert not os.path.exists(tmp_path / "rank0.ckpt")


def test_cross_process_probe_shape_and_reason():
    """The ONE capability probe tests and the bench share: (ok, reason);
    a negative verdict must name the jaxlib version (the canonical skip
    message)."""
    from orange3_spark_tpu.parallel.launcher import (
        cross_process_collectives_supported,
    )
    ok, reason = cross_process_collectives_supported()
    assert isinstance(ok, bool) and isinstance(reason, str)
    if not ok:
        import jaxlib
        assert jaxlib.__version__ in reason
    # the verdict is cached: a second call must be instant
    t0 = time.perf_counter()
    assert cross_process_collectives_supported() == (ok, reason)
    assert time.perf_counter() - t0 < 1.0


def test_multihost_drill_smoke():
    """tools/multihost_drill.py end to end (single-process gang): the
    SIGKILL'd host is detected typed, the gang restarts from the aligned
    epoch snapshot, loses 0 steps, and converges bitwise to the
    uninterrupted reference — with per-host goodput attribution."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "multihost_drill", os.path.join(repo, "tools", "multihost_drill.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    out = mod.run_drill(procs=1, rows=1024, epochs=3, chunk_rows=128)
    assert out["hosts_lost"] == 1
    assert out["gang_restarts"] == 1
    assert out["resume_parity_bitwise"] is True
    assert out["lost_work_steps"] == 0
    assert out["resumed_from_step"] == 8         # one epoch = 8 chunks
    for h in out["hosts"].values():
        assert "goodput" in h and "device_memory" in h
