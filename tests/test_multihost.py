"""Multi-host ingest (io/multihost.py): the make_array_from_process_local_data
assembly path, process row-slicing, and file-shard assignment — exercised
single-process (the multi-process branch runs with force_global=True, where
one process's local block IS the global array)."""

import jax
import numpy as np
import pytest

from orange3_spark_tpu.io.multihost import (
    process_row_slice,
    put_sharded,
    shard_paths,
)


def test_put_sharded_global_assembly_matches_device_put(session):
    x = np.arange(64 * 3, dtype=np.float32).reshape(64, 3)
    sh = session.row_sharding
    a = put_sharded(x, sh)
    b = put_sharded(x, sh, force_global=True)  # multi-process code path
    assert b.shape == (64, 3)
    assert b.sharding == sh
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_put_sharded_feeds_table_and_fit(session):
    """A table built through the global-assembly path must behave like the
    plain one end to end (fit + predict)."""
    from orange3_spark_tpu.core.domain import (
        ContinuousVariable, DiscreteVariable, Domain,
    )
    from orange3_spark_tpu.core.table import TpuTable
    from orange3_spark_tpu.models.logistic_regression import LogisticRegression

    rng = np.random.default_rng(0)
    X = rng.standard_normal((400, 4)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float32)
    dom = Domain([ContinuousVariable(f"f{i}") for i in range(4)],
                 DiscreteVariable("y", ("0", "1")))
    t = TpuTable.from_numpy(dom, X, y, session=session)
    m = LogisticRegression(max_iter=100).fit(t)
    assert np.mean(m.predict(t) == y) > 0.95


def test_process_row_slice_partitions_exactly(monkeypatch):
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    slices = []
    for pi in range(4):
        monkeypatch.setattr(jax, "process_index", lambda pi=pi: pi)
        slices.append(process_row_slice(10))
    covered = [i for s in slices for i in range(s.start, s.stop)]
    assert covered == list(range(10))          # disjoint, complete, ordered
    sizes = [s.stop - s.start for s in slices]
    assert max(sizes) - min(sizes) <= 1        # near-equal


def test_shard_paths_round_robin(monkeypatch):
    paths = [f"part-{i:03d}.csv" for i in range(7)]
    monkeypatch.setattr(jax, "process_count", lambda: 3)
    seen = []
    for pi in range(3):
        monkeypatch.setattr(jax, "process_index", lambda pi=pi: pi)
        seen.append(shard_paths(paths))
    flat = sorted(p for sub in seen for p in sub)
    assert flat == sorted(paths)               # every file exactly once
    assert all(len(s) in (2, 3) for s in seen)


def test_single_process_defaults():
    assert process_row_slice(100) == slice(0, 100)
    assert shard_paths(["b", "a"]) == ["a", "b"]


def test_shard_row_groups_partitions_single_parquet(tmp_path, monkeypatch):
    """Single-file parquet multihost splitting: the per-process row-group
    slices are contiguous, disjoint, exhaustive — and streaming each
    process's slice reassembles exactly the whole file (Spark's parquet
    input splits)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    import jax

    from orange3_spark_tpu.io.multihost import shard_row_groups
    from orange3_spark_tpu.io.streaming import parquet_raw_chunk_source

    p = str(tmp_path / "d.parquet")
    data = np.arange(70, dtype=np.float32)
    pq.write_table(pa.table({"v": data}), p, row_group_size=10)  # 7 groups

    monkeypatch.setattr(jax, "process_count", lambda: 3)
    slices = []
    for pi in range(3):
        monkeypatch.setattr(jax, "process_index", lambda pi=pi: pi)
        slices.append(shard_row_groups(p))
    assert [len(s) for s in slices] == [3, 2, 2]     # 7 groups over 3 procs
    assert sorted(sum(slices, [])) == list(range(7))
    for s in slices:                                  # contiguous ranges
        assert s == list(range(s[0], s[0] + len(s)))

    got = np.concatenate([
        np.concatenate(list(parquet_raw_chunk_source(
            p, chunk_rows=8, row_groups=tuple(s))()))
        for s in slices
    ])
    np.testing.assert_array_equal(got[:, 0], data)
