"""Pallas histogram kernel vs the segment_sum reference (SURVEY §2b trees)."""

import numpy as np
import pytest

import jax.numpy as jnp

from orange3_spark_tpu.ops.histogram import _hist_pallas, _hist_xla


@pytest.mark.parametrize("nodes,n_bins,s", [(1, 32, 3), (4, 16, 5), (8, 32, 2)])
def test_pallas_interpret_matches_xla(nodes, n_bins, s):
    rng = np.random.default_rng(0)
    n, d = 1000, 7
    B = jnp.asarray(rng.integers(0, n_bins, (n, d)), dtype=jnp.int32)
    S = jnp.asarray(rng.standard_normal((n, s)), dtype=jnp.float32)
    pos = jnp.asarray(rng.integers(0, nodes, n), dtype=jnp.int32)
    ref = _hist_xla(B, S, pos, nodes=nodes, n_bins=n_bins)
    got = _hist_pallas(B, S, pos, nodes=nodes, n_bins=n_bins, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-4)


def test_pallas_interpret_under_vmap_multiblock():
    """Forests vmap grow_tree over trees; the batched pallas_call must keep
    the per-tree accumulator init correct across MULTIPLE row blocks (the
    grid axis the init is keyed on). Verified on real TPU too (err ~1e-5)."""
    import functools

    rng = np.random.default_rng(2)
    t, n, d, s, n_bins, nodes = 3, 1200, 4, 2, 8, 2
    B = jnp.asarray(rng.integers(0, n_bins, (t, n, d)), dtype=jnp.int32)
    S = jnp.asarray(rng.standard_normal((t, n, s)), dtype=jnp.float32)
    pos = jnp.asarray(rng.integers(0, nodes, (t, n)), dtype=jnp.int32)
    import jax

    f = functools.partial(_hist_pallas, nodes=nodes, n_bins=n_bins,
                          interpret=True)
    g = functools.partial(_hist_xla, nodes=nodes, n_bins=n_bins)
    got = jax.vmap(f)(B, S, pos)
    ref = jax.vmap(g)(B, S, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-4)


def test_pallas_interpret_zero_weight_rows_ignored():
    rng = np.random.default_rng(1)
    n, d, s, n_bins = 512, 3, 2, 8
    B = jnp.asarray(rng.integers(0, n_bins, (n, d)), dtype=jnp.int32)
    S = jnp.asarray(rng.standard_normal((n, s)), dtype=jnp.float32)
    S = S.at[100:].set(0.0)  # dead rows carry zero stats
    pos = jnp.zeros((n,), jnp.int32)
    got = _hist_pallas(B, S, pos, nodes=1, n_bins=n_bins, interpret=True)
    ref = _hist_xla(B[:100], S[:100], pos[:100], nodes=1, n_bins=n_bins)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-4)
