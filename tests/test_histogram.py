"""Pallas histogram kernel vs the segment_sum reference (SURVEY §2b trees)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from orange3_spark_tpu.ops.histogram import _hist_pallas, _hist_xla


@pytest.mark.parametrize("seed", range(8))
def test_pallas_interpret_matches_xla_randomized(seed):
    """Randomized-shape parity sweep: the fixed-shape cases below only ever
    exercised a handful of (nodes, bins, stats, rows, features) points —
    this sweep randomizes all five, including rows that are NOT a multiple
    of the kernel's 128-lane block (the padding path), odd feature counts,
    and single-node/single-stat degenerate shapes (VERDICT Weak #3)."""
    rng = np.random.default_rng(100 + seed)
    nodes = int(rng.choice([1, 2, 3, 5, 8]))
    n_bins = int(rng.choice([4, 8, 16, 32, 64]))
    s = int(rng.integers(1, 6))
    n = int(rng.integers(1, 3000))
    d = int(rng.integers(1, 9))
    B = jnp.asarray(rng.integers(0, n_bins, (n, d)), dtype=jnp.int32)
    S = jnp.asarray(rng.standard_normal((n, s)), dtype=jnp.float32)
    pos = jnp.asarray(rng.integers(0, nodes, n), dtype=jnp.int32)
    ref = _hist_xla(B, S, pos, nodes=nodes, n_bins=n_bins)
    got = _hist_pallas(B, S, pos, nodes=nodes, n_bins=n_bins, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-4,
                               err_msg=f"shape=({nodes},{n_bins},{s},{n},{d})")


@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="compiled (Mosaic) Pallas path needs a real TPU: the kernel has "
           "only ever run in interpret mode on the CPU mesh — a TPU "
           "session picks this up automatically and exercises the "
           "compiled lowering against the XLA reference",
)
@pytest.mark.parametrize("nodes,n_bins,s", [(1, 32, 3), (4, 16, 5)])
def test_pallas_compiled_matches_xla_on_tpu(nodes, n_bins, s):
    rng = np.random.default_rng(7)
    n, d = 4096, 6
    B = jnp.asarray(rng.integers(0, n_bins, (n, d)), dtype=jnp.int32)
    S = jnp.asarray(rng.standard_normal((n, s)), dtype=jnp.float32)
    pos = jnp.asarray(rng.integers(0, nodes, n), dtype=jnp.int32)
    ref = _hist_xla(B, S, pos, nodes=nodes, n_bins=n_bins)
    got = _hist_pallas(B, S, pos, nodes=nodes, n_bins=n_bins,
                       interpret=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-4)


@pytest.mark.parametrize("nodes,n_bins,s", [(1, 32, 3), (4, 16, 5), (8, 32, 2)])
def test_pallas_interpret_matches_xla(nodes, n_bins, s):
    rng = np.random.default_rng(0)
    n, d = 1000, 7
    B = jnp.asarray(rng.integers(0, n_bins, (n, d)), dtype=jnp.int32)
    S = jnp.asarray(rng.standard_normal((n, s)), dtype=jnp.float32)
    pos = jnp.asarray(rng.integers(0, nodes, n), dtype=jnp.int32)
    ref = _hist_xla(B, S, pos, nodes=nodes, n_bins=n_bins)
    got = _hist_pallas(B, S, pos, nodes=nodes, n_bins=n_bins, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-4)


def test_pallas_interpret_under_vmap_multiblock():
    """Forests vmap grow_tree over trees; the batched pallas_call must keep
    the per-tree accumulator init correct across MULTIPLE row blocks (the
    grid axis the init is keyed on). Verified on real TPU too (err ~1e-5)."""
    import functools

    rng = np.random.default_rng(2)
    t, n, d, s, n_bins, nodes = 3, 1200, 4, 2, 8, 2
    B = jnp.asarray(rng.integers(0, n_bins, (t, n, d)), dtype=jnp.int32)
    S = jnp.asarray(rng.standard_normal((t, n, s)), dtype=jnp.float32)
    pos = jnp.asarray(rng.integers(0, nodes, (t, n)), dtype=jnp.int32)
    import jax

    f = functools.partial(_hist_pallas, nodes=nodes, n_bins=n_bins,
                          interpret=True)
    g = functools.partial(_hist_xla, nodes=nodes, n_bins=n_bins)
    got = jax.vmap(f)(B, S, pos)
    ref = jax.vmap(g)(B, S, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-4)


def test_pallas_interpret_zero_weight_rows_ignored():
    rng = np.random.default_rng(1)
    n, d, s, n_bins = 512, 3, 2, 8
    B = jnp.asarray(rng.integers(0, n_bins, (n, d)), dtype=jnp.int32)
    S = jnp.asarray(rng.standard_normal((n, s)), dtype=jnp.float32)
    S = S.at[100:].set(0.0)  # dead rows carry zero stats
    pos = jnp.zeros((n,), jnp.int32)
    got = _hist_pallas(B, S, pos, nodes=1, n_bins=n_bins, interpret=True)
    ref = _hist_xla(B[:100], S[:100], pos[:100], nodes=1, n_bins=n_bins)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-4)
