"""Text pipeline: Tokenizer/StopWords/NGram/HashingTF/CountVectorizer/IDF/Word2Vec."""

import numpy as np
import pytest

from orange3_spark_tpu.core.domain import ContinuousVariable, Domain, StringVariable
from orange3_spark_tpu.core.table import TpuTable
from orange3_spark_tpu.models.text import (
    IDF,
    CountVectorizer,
    HashingTF,
    NGram,
    RegexTokenizer,
    StopWordsRemover,
    Tokenizer,
    Word2Vec,
)


def _text_table(session, texts):
    dom = Domain([ContinuousVariable("x")], None, [StringVariable("text")])
    X = np.zeros((len(texts), 1), dtype=np.float32)
    metas = np.asarray(texts, dtype=object)[:, None]
    return TpuTable.from_numpy(dom, X, metas=metas, session=session)


def _tokens(table, col):
    names = [v.name for v in table.domain.metas]
    return table.metas[:, names.index(col)]


def test_tokenizer_lowercases_and_splits(session):
    t = _text_table(session, ["Hello World", "Foo  bar baz"])
    out = Tokenizer(input_col="text", output_col="tok").transform(t)
    toks = _tokens(out, "tok")
    assert toks[0] == ["hello", "world"]
    assert toks[1] == ["foo", "bar", "baz"]


def test_regex_tokenizer_min_length_and_findall(session):
    t = _text_table(session, ["ab, cde; f ghij"])
    out = RegexTokenizer(
        input_col="text", output_col="tok", pattern=r"\w+", gaps=False,
        min_token_length=2,
    ).transform(t)
    assert _tokens(out, "tok")[0] == ["ab", "cde", "ghij"]


def test_stopwords_removed(session):
    t = _text_table(session, ["the cat sat on the mat"])
    t = Tokenizer(input_col="text", output_col="tok").transform(t)
    out = StopWordsRemover(input_col="tok", output_col="clean").transform(t)
    assert _tokens(out, "clean")[0] == ["cat", "sat", "mat"]


def test_ngram(session):
    t = _text_table(session, ["a b c d"])
    t = Tokenizer(input_col="text", output_col="tok").transform(t)
    out = NGram(input_col="tok", output_col="bi", n=2).transform(t)
    assert _tokens(out, "bi")[0] == ["a b", "b c", "c d"]


def test_hashing_tf_counts_and_binary(session):
    t = _text_table(session, ["x x y", "z"])
    t = Tokenizer(input_col="text", output_col="tok").transform(t)
    out = HashingTF(input_col="tok", num_features=16).transform(t)
    X = out.to_numpy()[0]
    tf = X[:, 1:]  # first col is the original 'x' feature
    assert tf.shape == (2, 16)
    assert tf[0].sum() == 3.0 and tf[0].max() == 2.0  # 'x' twice, 'y' once
    assert tf[1].sum() == 1.0
    out_b = HashingTF(input_col="tok", num_features=16, binary=True).transform(t)
    assert out_b.to_numpy()[0][:, 1:].max() == 1.0


def test_count_vectorizer_vocab_and_min_df(session):
    docs = ["apple banana apple", "banana cherry", "apple banana", "dragonfruit"]
    t = _text_table(session, docs)
    t = Tokenizer(input_col="text", output_col="tok").transform(t)
    model = CountVectorizer(input_col="tok", min_df=2.0).fit(t)
    # dragonfruit + cherry appear in only 1 doc each
    assert set(model.vocabulary) == {"apple", "banana"}
    assert model.vocabulary[0] in ("apple", "banana")  # freq-ordered
    out = model.transform(t)
    X = out.to_numpy()[0]
    col = dict(zip(model.vocabulary, range(len(model.vocabulary))))
    assert X[0, 1 + col["apple"]] == 2.0
    assert X[3, 1:].sum() == 0.0


def test_idf_downweights_common_terms(session):
    docs = ["a b", "a c", "a d"]
    t = _text_table(session, docs)
    t = Tokenizer(input_col="text", output_col="tok").transform(t)
    cv = CountVectorizer(input_col="tok", min_df=1.0).fit(t)
    t2 = cv.transform(t)
    count_cols = tuple(f"cv_{w}" for w in cv.vocabulary)
    idf_model = IDF(input_cols=count_cols).fit(t2)
    out = idf_model.transform(t2)
    X = out.to_numpy()[0]
    names = [v.name for v in out.domain.attributes]
    # 'a' in every doc -> idf log(4/4)=0; rare terms get positive weight
    a_col = names.index("cv_a")
    assert np.allclose(X[:, a_col], 0.0, atol=1e-6)
    b_col = names.index("cv_b")
    assert X[0, b_col] > 0


def test_word2vec_groups_cooccurring_words(session):
    rng = np.random.default_rng(0)
    docs = []
    for _ in range(150):
        docs.append(" ".join(rng.permutation(["cat", "dog", "pet"]).tolist()))
        docs.append(" ".join(rng.permutation(["car", "road", "drive"]).tolist()))
    t = _text_table(session, docs)
    t = Tokenizer(input_col="text", output_col="tok").transform(t)
    model = Word2Vec(
        input_col="tok", vector_size=16, min_count=5, window_size=2,
        max_iter=30, step_size=0.5, seed=1,
    ).fit(t)
    assert set(model.vocabulary) == {"cat", "dog", "pet", "car", "road", "drive"}
    syn = model.find_synonyms("cat", num=2)
    assert {w for w, _ in syn} <= {"dog", "pet"}
    out = model.transform(t)
    assert out.to_numpy()[0].shape[1] == 1 + 16


def test_word2vec_transform_doc_vectors_cluster(session):
    docs = ["cat dog", "dog cat", "car road", "road car"] * 40
    t = _text_table(session, docs)
    t = Tokenizer(input_col="text", output_col="tok").transform(t)
    model = Word2Vec(input_col="tok", vector_size=8, min_count=5,
                     window_size=2, max_iter=20, step_size=0.5, seed=2).fit(t)
    out = model.transform(t)
    X = out.to_numpy()[0][:, 1:]
    # doc vectors of same-topic docs should be closer than cross-topic
    d_same = np.linalg.norm(X[0] - X[1])
    d_cross = np.linalg.norm(X[0] - X[2])
    assert d_same < d_cross
