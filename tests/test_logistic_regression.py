import numpy as np
import pytest

from orange3_spark_tpu.datasets import load_iris, make_classification
from orange3_spark_tpu.models.logistic_regression import LogisticRegression


def test_iris_accuracy_vs_sklearn(session, iris):
    """BASELINE config 1: Iris LogReg, correctness vs sklearn."""
    est = LogisticRegression(max_iter=200, reg_param=1e-4)
    model = est.fit(iris)
    pred = model.predict(iris)
    y = np.asarray(iris.to_numpy()[1])[:, 0]
    acc = np.mean(pred == y)

    from sklearn.linear_model import LogisticRegression as SkLR

    X = iris.to_numpy()[0]
    sk = SkLR(max_iter=500, C=1e4).fit(X, y)
    sk_acc = sk.score(X, y)
    assert acc >= sk_acc - 0.02, f"ours {acc} vs sklearn {sk_acc}"
    agreement = np.mean(pred == sk.predict(X))
    assert agreement >= 0.95


def test_binary_classification(session):
    t = make_classification(600, 10, n_classes=2, seed=1, noise=0.1, session=session)
    model = LogisticRegression(max_iter=100).fit(t)
    pred = model.predict(t)
    y = t.to_numpy()[1][:, 0]
    assert np.mean(pred == y) > 0.95


def test_probabilities_sum_to_one(session, iris):
    model = LogisticRegression(max_iter=50).fit(iris)
    proba = model.predict_proba(iris)
    assert proba.shape == (150, 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)


def test_transform_appends_columns(session, iris):
    model = LogisticRegression(max_iter=50).fit(iris)
    out = model.transform(iris)
    names = [v.name for v in out.domain.attributes]
    assert "prediction" in names
    assert any(n.startswith("probability_") for n in names)
    assert out.n_attrs == iris.n_attrs + 3 + 1


def test_weighted_fit_ignores_zero_weight_rows(session):
    """Filtered rows must not influence the fit (Spark filter semantics)."""
    t = make_classification(400, 5, n_classes=2, seed=2, session=session)
    X, Y, _ = t.to_numpy()
    # corrupt second half with flipped labels, then filter it out
    Y2 = Y.copy()
    Y2[200:] = 1 - Y2[200:]
    from orange3_spark_tpu.core.table import TpuTable

    corrupt = TpuTable.from_numpy(t.domain, X, Y2, session=session)
    import jax.numpy as jnp

    mask = jnp.arange(corrupt.n_pad) < 200
    filtered = corrupt.filter(mask)
    m_filtered = LogisticRegression(max_iter=100).fit(filtered)

    clean_half = TpuTable.from_numpy(t.domain, X[:200], Y[:200], session=session)
    m_clean = LogisticRegression(max_iter=100).fit(clean_half)

    np.testing.assert_allclose(
        np.asarray(m_filtered.coef), np.asarray(m_clean.coef), rtol=1e-3, atol=1e-4
    )


def test_regularization_shrinks_coefficients(session, iris):
    loose = LogisticRegression(max_iter=100, reg_param=0.0).fit(iris)
    tight = LogisticRegression(max_iter=100, reg_param=1.0).fit(iris)
    assert np.linalg.norm(np.asarray(tight.coef)) < np.linalg.norm(np.asarray(loose.coef))


def test_standardization_off(session, iris):
    model = LogisticRegression(max_iter=200, standardization=False, reg_param=1e-4).fit(iris)
    pred = model.predict(iris)
    y = iris.to_numpy()[1][:, 0]
    assert np.mean(pred == y) > 0.9


def test_fit_metrics_recorded(session, iris):
    est = LogisticRegression(max_iter=20)
    est.fit(iris)
    assert est.last_fit_metrics["rows_per_sec_per_chip"] > 0


def test_max_iter_zero_returns_init(session, iris):
    """MLlib maxIter=0 semantics: no optimization step, zero coefficients."""
    model = LogisticRegression(max_iter=0).fit(iris)
    assert model.n_iter_ == 0
    assert np.allclose(np.asarray(model.coef), 0.0)


def test_binomial_threshold_changes_predictions(session):
    t = make_classification(300, 5, n_classes=2, seed=3, noise=2.0, session=session)
    model = LogisticRegression(max_iter=50).fit(t)
    low = model.params.replace(threshold=0.01)
    high = model.params.replace(threshold=0.99)
    model.params = low
    pred_low = model.predict(t)
    model.params = high
    pred_high = model.predict(t)
    # low threshold predicts class 1 almost everywhere, high almost nowhere
    assert pred_low.mean() > pred_high.mean()


def test_elastic_net_fits(session, iris):
    """elastic_net_param>0 takes the OWLQN path and produces a usable model
    (full parity coverage lives in test_elastic_net.py)."""
    model = LogisticRegression(
        max_iter=300, reg_param=1e-3, elastic_net_param=0.5
    ).fit(iris)
    y = np.asarray(iris.to_numpy()[1])[:, 0]
    assert np.mean(model.predict(iris) == y) > 0.9
